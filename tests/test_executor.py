"""Warm-start executor: persistent compile cache + AOT warm-up
(engine/compilecache.py), donated carries, and multi-block fused
dispatch (``Plan.blocks_per_dispatch``).

The fused-dispatch bit-identity contract tested here (and documented on
``Simulation._mega_block_fn``): megablocks are bit-identical to
per-block dispatch for every reduce statistic and for the scan-family
producers everywhere.  The one caveat is the WIDE producer's raw
per-second arrays under the suite's 8-virtual-device CPU config —
XLA:CPU compiles a fusion embedded in a loop body with different
vector-epilogue boundaries than the same fusion at a jit root, so
``pv`` can differ by one ulp at a handful of seconds per block; those
comparisons use a one-ulp relative tolerance instead of exact equality
(single-device CPU is exact; the reduce folds absorb the ulps).
"""

import json
import os

import jax
import numpy as np
import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine import Simulation, compilecache
from tmhpvsim_tpu.engine import checkpoint as ckpt
from tmhpvsim_tpu.obs.metrics import MetricsRegistry, use_registry
from tmhpvsim_tpu.obs.report import (
    REPORT_SCHEMA_VERSION,
    validate_report,
)


def cfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=1800,
        n_chains=2,
        seed=11,
        block_s=600,
        dtype="float32",
    )
    base.update(kw)
    return SimConfig(**base)


def eq_tree(a, b, what):
    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    assert ta == tb, (what, ta, tb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)


def ens_arrays(sim):
    # run_ensemble yields BlockResults lazily; materialise to host now
    return [(np.asarray(b.meter), np.asarray(b.pv))
            for b in sim.run_ensemble()]


# ---------------------------------------------------------------------------
# persistent compile cache: AOT warm-up populates it, rebuild is all-warm
# ---------------------------------------------------------------------------

class TestWarmCache:
    def test_second_build_compiles_zero_times(self, tmp_path):
        """Against a cache dir populated by the first build's AOT
        warm-up, a process-equivalent rebuild must deserialise every
        executable — zero fresh compiles (the ISSUE's acceptance
        criterion; conftest's autouse fixture restores the suite's
        ``.jax_cache`` afterwards)."""
        d = compilecache.configure(str(tmp_path))
        assert compilecache.is_configured()
        assert d is not None and d.startswith(str(tmp_path))

        c = cfg(output="reduce", block_impl="scan", duration_s=1200,
                blocks_per_dispatch=2)
        reg1 = MetricsRegistry()
        with use_registry(reg1):
            sim = Simulation(c)
        assert sim._k_dispatch == 2
        s1 = reg1.snapshot()["counters"]
        n_targets = len(sim.aot_targets())
        # scan_acc + the k=2 mega jit + the state/acc resume copies
        assert n_targets == 4
        assert s1.get("executor.aot_warmup_total", 0) == n_targets
        assert s1.get("executor.aot_warmup_errors_total", 0) == 0
        cache_files = [f for _, _, fns in os.walk(str(tmp_path)) for f in fns]
        assert cache_files, "AOT warm-up left the cache dir empty"

        reg2 = MetricsRegistry()
        with use_registry(reg2):
            Simulation(c)
        s2 = reg2.snapshot()["counters"]
        # the per-instance jits must deserialise from the persistent
        # cache; the module-level resume copies are shared with build 1
        # and may be served from jax's in-process executable cache
        # without any cache event — either way nothing compiles cold
        warm = int(s2.get("executor.compile_warm_total", 0))
        assert warm >= 2
        assert s2.get("executor.compile_cold_total", 0) == 0

        doc = compilecache.executor_doc(reg2)
        assert doc["compile_warm"] == warm
        assert doc["compile_cold"] == 0
        assert doc["aot_warmup"] == n_targets
        assert doc["cache_dir"] == d

    def test_off_spellings_disable(self):
        assert compilecache.configure("off") is None
        assert not compilecache.is_configured()
        assert compilecache.cache_dir() is None
        # unconfigured -> Simulation build must not pay AOT warm-up
        reg = MetricsRegistry()
        with use_registry(reg):
            Simulation(cfg(output="reduce", block_impl="scan",
                           duration_s=1200))
        assert "executor.aot_warmup_total" not in reg.snapshot()["counters"]


# ---------------------------------------------------------------------------
# multi-block fused dispatch: bit-identity vs per-block dispatch
# ---------------------------------------------------------------------------

class TestFusedDispatchBitIdentity:
    @pytest.mark.parametrize("impl", ["wide", "scan", "scan2"])
    def test_reduce_matches_per_block(self, impl):
        base = Simulation(cfg(output="reduce", block_impl=impl)).run_reduced()
        for k in (2, 3):  # k=3 divides the 3 blocks; k=2 leaves a remainder
            sim = Simulation(cfg(output="reduce", block_impl=impl,
                                 blocks_per_dispatch=k))
            assert sim._k_dispatch == k
            assert sim.plan.blocks_per_dispatch == k
            eq_tree(base, sim.run_reduced(), f"reduce {impl} k={k}")

    @pytest.mark.parametrize("impl", ["wide", "scan", "scan2"])
    def test_ensemble_matches_per_block(self, impl):
        e1 = ens_arrays(Simulation(cfg(output="ensemble", block_impl=impl)))
        e2 = ens_arrays(Simulation(cfg(output="ensemble", block_impl=impl,
                                       blocks_per_dispatch=2)))
        assert len(e1) == len(e2) == 3
        for i, (x, y) in enumerate(zip(e1, e2)):
            np.testing.assert_array_equal(x[0], y[0],
                                          err_msg=f"ens {impl} meter b{i}")
            if impl == "wide":  # one-ulp CPU epilogue caveat (module doc)
                np.testing.assert_allclose(x[1], y[1], rtol=3e-7, atol=0,
                                           err_msg=f"ens {impl} pv b{i}")
            else:
                np.testing.assert_array_equal(x[1], y[1],
                                              err_msg=f"ens {impl} pv b{i}")

    def test_trace_matches_per_block(self):
        b1 = list(Simulation(cfg()).run_blocks())
        b2 = list(Simulation(cfg(blocks_per_dispatch=3)).run_blocks())
        assert len(b1) == len(b2) == 3
        for x, y in zip(b1, b2):
            np.testing.assert_array_equal(x.meter, y.meter)
            np.testing.assert_array_equal(x.epoch, y.epoch)
            # one-ulp CPU epilogue caveat on the wide producer (module doc)
            np.testing.assert_allclose(x.pv, y.pv, rtol=3e-7, atol=0)

    def test_reduce_with_telemetry_matches_per_block(self):
        b = Simulation(cfg(output="reduce", block_impl="scan",
                           telemetry="light")).run_reduced()
        g = Simulation(cfg(output="reduce", block_impl="scan",
                           telemetry="light",
                           blocks_per_dispatch=3)).run_reduced()
        eq_tree(b, g, "reduce telemetry k=3")

    def test_on_block_sees_per_block_acc_snapshots(self):
        """The mega path still surfaces one accumulator snapshot per
        BLOCK (not per dispatch), each bit-identical to per-block
        folding.  on_block pytrees are borrowed (run_reduced docstring):
        the donated carry reuses the buffer a zero-copy np.asarray view
        would alias, so snapshots must copy with np.array."""
        snap1, snap2 = [], []
        Simulation(cfg(output="reduce", block_impl="scan")).run_reduced(
            on_block=lambda bi, st, acc: snap1.append(
                jax.tree.map(np.array, acc)))
        Simulation(cfg(output="reduce", block_impl="scan",
                       blocks_per_dispatch=3)).run_reduced(
            on_block=lambda bi, st, acc: snap2.append(
                jax.tree.map(np.array, acc)))
        assert len(snap1) == len(snap2) == 3
        for i, (a, b) in enumerate(zip(snap1, snap2)):
            eq_tree(a, b, f"on_block snapshot {i}")

    def test_dispatch_counters(self):
        """k blocks per dispatch -> ceil(n_blocks / k) dispatches, while
        engine.blocks_total still counts blocks."""
        reg = MetricsRegistry()
        with use_registry(reg):
            Simulation(cfg(output="reduce", block_impl="scan",
                           blocks_per_dispatch=2)).run_reduced()
        c = reg.snapshot()["counters"]
        assert c["engine.blocks_total"] == 3
        assert c["executor.dispatches_total"] == 2  # mega [0,1] + block 2

        reg = MetricsRegistry()
        with use_registry(reg):
            Simulation(cfg(output="reduce",
                           block_impl="scan")).run_reduced()
        c = reg.snapshot()["counters"]
        assert c["engine.blocks_total"] == 3
        assert c["executor.dispatches_total"] == 3


class TestShardedFusedDispatch:
    def test_sharded_reduce_matches_per_block(self):
        from tmhpvsim_tpu.parallel.mesh import ShardedSimulation

        b = ShardedSimulation(cfg(output="reduce", block_impl="scan",
                                  n_chains=8)).run_reduced()
        g = ShardedSimulation(cfg(output="reduce", block_impl="scan",
                                  n_chains=8,
                                  blocks_per_dispatch=3)).run_reduced()
        eq_tree(b, g, "sharded reduce k=3")

    def test_sharded_ensemble_matches_per_block(self):
        from tmhpvsim_tpu.parallel.mesh import ShardedSimulation

        e1 = ens_arrays(ShardedSimulation(cfg(output="ensemble",
                                              block_impl="scan",
                                              n_chains=8)))
        e2 = ens_arrays(ShardedSimulation(cfg(output="ensemble",
                                              block_impl="scan", n_chains=8,
                                              blocks_per_dispatch=2)))
        assert len(e1) == len(e2) == 3
        for i, (x, y) in enumerate(zip(e1, e2)):
            np.testing.assert_array_equal(x[0], y[0],
                                          err_msg=f"shard ens meter b{i}")
            np.testing.assert_array_equal(x[1], y[1],
                                          err_msg=f"shard ens pv b{i}")


# ---------------------------------------------------------------------------
# buffer donation: caller-held resume pytrees survive the donated paths
# ---------------------------------------------------------------------------

def _materialize(leaf):
    if hasattr(leaf, "dtype") and jax.dtypes.issubdtype(
            leaf.dtype, jax.dtypes.prng_key):
        return np.asarray(jax.random.key_data(leaf))
    return np.asarray(leaf)


class TestDonation:
    def test_caller_held_resume_refs_survive(self):
        """run_reduced donates its state/accumulator carries, but a
        caller-provided resume tree must stay readable afterwards (the
        defensive copy in the dispatch loop, simulation.py) — resume
        checkpoints are saved from exactly these references."""
        sim = Simulation(cfg(output="reduce", block_impl="scan"))
        sim.run_reduced()
        st = sim.state                      # caller-held device pytrees
        acc_dev = sim._last_acc
        acc_np = {k: np.asarray(v) for k, v in acc_dev.items()}

        sim2 = Simulation(cfg(output="reduce", block_impl="scan",
                              duration_s=3600, blocks_per_dispatch=2))
        sim2.run_reduced(state=st, acc=acc_dev, start_block=3)

        # every caller-held buffer must still be alive (donation would
        # raise "Array has been deleted" here) and bit-unchanged
        jax.tree.map(_materialize, st)
        for k, v in acc_np.items():
            np.testing.assert_array_equal(v, np.asarray(acc_dev[k]))


# ---------------------------------------------------------------------------
# checkpointing across megablock boundaries
# ---------------------------------------------------------------------------

class TestCheckpointMidMegablock:
    def test_restore_lands_on_correct_block_boundary(self, tmp_path):
        """With fused dispatch the device state only advances at
        megablock boundaries, so the app-side save gate
        (``sim.state_block == bi + 1``) must skip the interior blocks of
        a megablock and fire exactly at its boundary; resuming from that
        checkpoint must match an uninterrupted per-block run bit for
        bit."""
        c4 = dict(output="reduce", block_impl="scan", duration_s=2400)
        straight = Simulation(cfg(**c4)).run_reduced()

        path = str(tmp_path / "mega.npz")
        a = Simulation(cfg(blocks_per_dispatch=3, **c4))  # [0,1,2] + [3]
        saves = []

        class Stop(Exception):
            pass

        def save_then_crash(bi, state, acc):
            if a.state_block == bi + 1:  # the apps/pvsim.py gate
                ckpt.save(path, {"state": state, "acc": acc}, bi + 1,
                          a.config)
                saves.append(bi)
                raise Stop

        with pytest.raises(Stop):
            a.run_reduced(on_block=save_then_crash)
        # gate skipped the megablock interior (bi=0,1) and fired at its
        # boundary: state_block was 3 throughout the first dispatch
        assert saves == [2]

        b = Simulation(cfg(blocks_per_dispatch=3, **c4))
        tree, nb = ckpt.load(path, b.config)
        assert nb == 3
        resumed = b.run_reduced(state=tree["state"], acc=tree["acc"],
                                start_block=nb)
        eq_tree(straight, resumed, "mid-megablock checkpoint resume")


# ---------------------------------------------------------------------------
# run report: schema v4 round-trip + v1..v3 back-compat
# ---------------------------------------------------------------------------

class TestReportSchemaV4:
    def _doc(self):
        with use_registry(MetricsRegistry()):
            sim = Simulation(cfg(output="reduce", block_impl="scan",
                                 telemetry="light", blocks_per_dispatch=2))
            sim.run_reduced()
            return sim.run_report()

    def test_v4_round_trips_with_executor_section(self):
        doc = self._doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 16
        ex = doc["executor"]
        assert ex["blocks_per_dispatch"] == 2
        assert ex["dispatches"] == 2  # 3 blocks, k=2: mega [0,1] + block 2
        validate_report(json.loads(json.dumps(doc)))

    def test_v3_documents_still_validate(self):
        """PR-4 builds wrote v3 docs without an executor section; the v4
        validator must keep accepting them."""
        doc = self._doc()
        doc["schema_version"] = 3
        doc.pop("executor", None)
        validate_report(doc)

    def test_v2_documents_still_validate(self):
        doc = self._doc()
        doc["schema_version"] = 2
        doc.pop("executor", None)
        doc.pop("streaming", None)
        validate_report(doc)

    def test_v1_documents_still_validate(self):
        doc = self._doc()
        doc["schema_version"] = 1
        doc.pop("executor", None)
        doc.pop("streaming", None)
        doc.pop("telemetry", None)
        validate_report(doc)


# ---------------------------------------------------------------------------
# autotune plan-cache back-compat (MIGRATION.md: old entries still load)
# ---------------------------------------------------------------------------

class TestPlanCacheBackCompat:
    def test_pre_fused_dispatch_entries_still_load(self):
        """Plan-cache entries persisted before blocks_per_dispatch
        existed carry no such key; they must load as per-block
        dispatch, not raise."""
        from tmhpvsim_tpu.engine import autotune

        plan = autotune._plan_from_entry({"plan": {
            "block_impl": "scan", "scan_unroll": 1,
            "stats_fusion": "fused", "slab_chains": 4096}})
        assert plan.blocks_per_dispatch == 1
        assert plan.source == "cache"

    def test_malformed_dispatch_factor_rejected(self):
        from tmhpvsim_tpu.engine import autotune

        with pytest.raises(ValueError, match="malformed"):
            autotune._plan_from_entry({"plan": {
                "block_impl": "scan", "scan_unroll": 1,
                "stats_fusion": "fused", "slab_chains": 4096,
                "blocks_per_dispatch": 0}})


# ---------------------------------------------------------------------------
# acceptance (slow lane): fused dispatch is no slower than per-block
# ---------------------------------------------------------------------------

def test_fused_dispatch_no_slower_65536_chains():
    """At the headline chain count, k=3 fused dispatch must not run
    slower than per-block dispatch (both arms timed on their second,
    compile-free run; 25% slack for timer noise on the shared CPU
    host)."""
    import time

    def timed_second_run(k):
        sim = Simulation(cfg(output="reduce", block_impl="scan",
                             n_chains=65536, blocks_per_dispatch=k))
        sim.run_reduced()              # compile + first dispatch
        t0 = time.perf_counter()
        sim.run_reduced()
        return time.perf_counter() - t0

    per_block = timed_second_run(1)
    fused = timed_second_run(3)
    assert fused <= per_block * 1.25, (fused, per_block)
