"""Live ops plane (obs/live.py, obs/cost.py, trace propagation):

* OpenMetrics exposition format + the pinned ``quantile_from_snapshot``
  edge rules (single-bucket snapshots, boundary quantiles);
* the static cost model, its v10 ``cost`` report section (v1–v9 docs
  still validate), ``device.cost.*`` gauges, and tools/cost_report.py;
* the HTTP endpoints (/metrics /healthz /readyz /flight) over both
  lifecycles, readiness semantics under drain + breaker chaos (driven
  with runtime/faults.py and an injected breaker clock — no sleeps);
* cross-process trace propagation: off-by-default wire identity,
  stamp/extract/scope mechanics, HLO byte-identity with propagation on,
  the 8-client serve soak proving one trace id correlates
  client → broker → batcher → fused dispatch → reply on all three
  transports, and ``tools/trace_stats.py --stitch``;
* the bench_trend ``cost`` column.

Port-binding tests carry the ``netport`` marker (deselect with
``-m 'not netport'`` in sandboxes that forbid localhost listeners).
"""

import asyncio
import contextlib
import json
import pathlib
import subprocess
import sys
import urllib.request

import pytest

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.obs import cost as obs_cost
from tmhpvsim_tpu.obs import trace as obs_trace
from tmhpvsim_tpu.obs.live import ObsServer, maybe_obs_server
from tmhpvsim_tpu.obs.metrics import (
    OPENMETRICS_CONTENT_TYPE,
    MetricsRegistry,
    quantile_from_snapshot,
    use_registry,
)
from tmhpvsim_tpu.obs.report import (
    REPORT_SCHEMA_VERSION,
    RunReport,
    validate_report,
)
from tmhpvsim_tpu.obs.trace import Tracer
from tmhpvsim_tpu.runtime import faults
from tmhpvsim_tpu.runtime.faults import FaultPlan
from tmhpvsim_tpu.runtime.tcpbroker import TcpFanoutBroker
from tmhpvsim_tpu.serve.server import (
    ScenarioClient,
    ScenarioServer,
    ServeConfig,
)

# reuse test_amqp's fake aio_pika (registers the fixture here too)
from test_amqp import fake_aio_pika  # noqa: F401

REPO = pathlib.Path(__file__).resolve().parent.parent
TRACE_STATS = REPO / "tools" / "trace_stats.py"
COST_REPORT = REPO / "tools" / "cost_report.py"
BENCH_TREND = REPO / "tools" / "bench_trend.py"
sys.path.insert(0, str(REPO / "tools"))

import trace_stats  # noqa: E402  (the stitcher, imported as a library)


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def scfg(**kw):
    base = dict(
        start="2019-09-05 10:00:00",
        duration_s=120,
        n_chains=4,
        seed=7,
        block_s=60,
        dtype="float32",
        output="reduce",
        block_impl="scan",
        scan_unroll=1,
    )
    base.update(kw)
    return SimConfig(**base)


async def _http_get(port: int, path: str, host: str = "127.0.0.1"):
    """Raw one-shot GET against an ObsServer; returns (status, headers,
    body-bytes)."""
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: {host}\r\n\r\n"
                 .encode("ascii"))
    await writer.drain()
    raw = await reader.read()
    writer.close()
    with contextlib.suppress(Exception):
        await writer.wait_closed()
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split()[1])
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, body


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------


class TestOpenMetrics:
    def test_counter_total_suffix_and_eof(self):
        reg = MetricsRegistry()
        reg.counter("broker.published").inc(3)
        reg.gauge("clock.lag_s").set(1.5)
        text = reg.openmetrics_text()
        assert "# TYPE tmhpvsim_broker_published counter" in text
        assert "tmhpvsim_broker_published_total 3" in text
        assert "tmhpvsim_clock_lag_s 1.5" in text
        # the two spec-mandated divergences from Prometheus text format
        assert text.endswith("# EOF\n")
        prom = reg.prometheus_text()
        assert "tmhpvsim_broker_published 3" in prom
        assert "# EOF" not in prom

    def test_histogram_exposition_is_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.5, 2.0):
            h.observe(v)
        text = reg.openmetrics_text(prefix="")
        assert 'lat_bucket{le="0.1"} 1' in text
        assert 'lat_bucket{le="1"} 3' in text
        assert 'lat_bucket{le="+Inf"} 4' in text
        assert "lat_count 4" in text

    def test_exposition_parses(self):
        """Every sample line is ``name[{labels}] value`` — the shape an
        OpenMetrics scraper tokenises."""
        import re

        reg = MetricsRegistry()
        reg.counter("a.b-c").inc()
        reg.gauge("g").set(-0.25)
        reg.histogram("h").observe(1e9)
        lines = reg.openmetrics_text().splitlines()
        assert lines[-1] == "# EOF"
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9eE.+-]+$')
        for line in lines[:-1]:
            if line.startswith("#"):
                assert line.startswith("# TYPE "), line
            else:
                assert sample.match(line), line

    def test_content_type_constant(self):
        assert "application/openmetrics-text" in OPENMETRICS_CONTENT_TYPE


# ---------------------------------------------------------------------------
# quantile_from_snapshot: the pinned edge rules
# ---------------------------------------------------------------------------


class TestQuantileEdges:
    def test_single_bucket_interpolates_observed_span(self):
        snap = {"count": 4, "min": 0.2, "max": 0.6,
                "buckets": [(1.0, 4), (5.0, 4)]}
        assert quantile_from_snapshot(snap, 0.5) == pytest.approx(0.4)
        assert quantile_from_snapshot(snap, 0.0) == pytest.approx(0.2)
        assert quantile_from_snapshot(snap, 1.0) == pytest.approx(0.6)

    def test_single_bucket_without_minmax_returns_bound(self):
        # a snapshot rebuilt from sparse JSON: min/max lost
        snap = {"count": 4, "buckets": [(1.0, 4), (5.0, 4)]}
        assert quantile_from_snapshot(snap, 0.5) == pytest.approx(1.0)

    def test_boundary_quantile_returns_bucket_bound(self):
        # q*count lands EXACTLY on the first bucket's cumulative count:
        # the answer is that bound, never an interpolation past it
        snap = {"count": 10, "min": 0.0, "max": 2.0,
                "buckets": [(1.0, 5), (2.0, 10)]}
        assert quantile_from_snapshot(snap, 0.5) == pytest.approx(1.0)
        # interior target interpolates as usual
        assert quantile_from_snapshot(snap, 0.75) == pytest.approx(1.5)

    def test_beyond_last_finite_bucket_is_observed_max(self):
        snap = {"count": 10, "min": 0.5, "max": 9.0,
                "buckets": [(1.0, 5)]}
        assert quantile_from_snapshot(snap, 0.9) == pytest.approx(9.0)

    def test_empty_and_zero_count_are_none(self):
        assert quantile_from_snapshot(None, 0.5) is None
        assert quantile_from_snapshot({}, 0.5) is None
        assert quantile_from_snapshot({"count": 0, "buckets": []},
                                      0.5) is None


# ---------------------------------------------------------------------------
# cost model + v10 report section
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_base_cell_is_the_round5_anchor(self):
        doc = obs_cost.model_cost()
        assert doc["model"] == obs_cost.MODEL
        assert doc["flops_per_site_s"] == obs_cost.BASE_FLOPS_PER_SITE_S
        assert doc["bytes_per_site_s"] == obs_cost.BASE_BYTES_PER_SITE_S
        assert (doc["block_impl"], doc["compute_dtype"],
                doc["kernel_impl"]) == ("scan", "f32", "exact")

    def test_axis_factors_compose(self):
        doc = obs_cost.model_cost("scan2", "bf16", "table")
        assert doc["flops_per_site_s"] == pytest.approx(
            390.0 * 0.98 * 1.0 * 0.45, abs=0.01)
        assert doc["bytes_per_site_s"] == pytest.approx(
            96.0 * 0.97 * 0.55 * 1.15, abs=0.01)

    def test_auto_and_unknown_axes_price_as_default(self):
        assert obs_cost.model_cost("auto", None, "") \
            == obs_cost.model_cost()
        weird = obs_cost.model_cost("hypothetical-impl")
        assert weird["flops_per_site_s"] \
            == obs_cost.BASE_FLOPS_PER_SITE_S

    def test_cost_doc_north_star_and_roofline(self):
        doc = obs_cost.cost_doc(site_s_per_s=obs_cost.NORTH_STAR,
                                device_kind="TPU v5 lite")
        assert doc["north_star_frac"] == pytest.approx(1.0)
        assert doc["achieved_gflops"] == pytest.approx(
            390.0 * obs_cost.NORTH_STAR / 1e9, rel=1e-3)
        assert doc["roofline_frac_vpu"] == pytest.approx(
            doc["achieved_gflops"] / 6100.0, rel=1e-3)
        assert doc["peaks"]["vpu_is_estimate"] is True
        assert doc["basis"] == "model"
        assert obs_cost.validate_cost(doc) == []

    def test_unknown_device_has_no_roofline(self):
        doc = obs_cost.cost_doc(site_s_per_s=1e6, device_kind="cpu")
        assert "roofline_frac_vpu" not in doc
        assert "peaks" not in doc
        assert obs_cost.validate_cost(doc) == []

    def test_measured_inputs_take_precedence(self):
        doc = obs_cost.cost_doc(site_s_per_s=1e9,
                                measured_flops_per_site_s=500.0,
                                measured_bytes_per_site_s=100.0)
        assert doc["basis"] == "measured"
        assert doc["achieved_gflops"] == pytest.approx(500.0)
        assert doc["achieved_gbs"] == pytest.approx(100.0)
        # the static prediction stays alongside as a model-quality signal
        assert doc["flops_per_site_s"] == 390.0

    def test_no_rate_no_achieved_fields(self):
        doc = obs_cost.cost_doc(site_s_per_s=None)
        assert "achieved_gflops" not in doc
        assert "north_star_frac" not in doc
        assert obs_cost.validate_cost(doc) == []

    def test_publish_gauges_numeric_fields_only(self):
        reg = MetricsRegistry()
        doc = obs_cost.cost_doc(site_s_per_s=1.2e9,
                                device_kind="TPU v5 lite")
        obs_cost.publish_gauges(reg, doc)
        gauges = reg.snapshot()["gauges"]
        assert gauges["device.cost.north_star_frac"] \
            == doc["north_star_frac"]
        assert gauges["device.cost.achieved_gflops"] \
            == doc["achieved_gflops"]
        assert "device.cost.model" not in gauges  # strings don't gauge

    def test_validate_cost_catches_malformed(self):
        doc = obs_cost.cost_doc(site_s_per_s=1e6)
        assert obs_cost.validate_cost("nope")
        bad = dict(doc)
        del bad["model"]
        bad["north_star_frac"] = "0.18"
        bad["basis"] = "vibes"
        errs = "; ".join(obs_cost.validate_cost(bad))
        assert "cost.model" in errs
        assert "cost.north_star_frac" in errs
        assert "cost.basis" in errs

    def test_north_star_matches_roadmap(self):
        # 100k users x 1 simulated year / 1 min wall on 8 chips
        assert obs_cost.NORTH_STAR == pytest.approx(
            100_000 * 365.25 * 86400 / 60.0 / 8.0)


class TestReportV10:
    def test_cost_section_round_trips(self):
        assert REPORT_SCHEMA_VERSION == 16
        rep = RunReport("test")
        rep.cost = obs_cost.cost_doc(
            site_s_per_s=1.2e9, block_impl="scan2",
            compute_dtype="bf16", kernel_impl="table",
            device_kind="TPU v5 lite")
        doc = json.loads(json.dumps(rep.doc()))
        assert doc["schema_version"] == 16
        validate_report(doc)

    def test_malformed_cost_section_rejected(self):
        rep = RunReport("test")
        rep.cost = {"model": None}
        with pytest.raises(ValueError, match="cost"):
            rep.doc()

    @pytest.mark.parametrize("old", list(range(1, 10)))
    def test_v1_v9_documents_still_validate(self, old):
        since = {"telemetry": 2, "streaming": 3, "executor": 4,
                 "fleet": 5, "serving": 6, "resilience": 7,
                 "precision": 8, "probe": 8, "cost": 10}
        rep = RunReport("test")
        rep.cost = obs_cost.cost_doc(site_s_per_s=1e6)
        doc = rep.doc()
        legacy = {k: v for k, v in doc.items()
                  if since.get(k, 1) <= old}
        legacy["schema_version"] = old
        validate_report(legacy)


# ---------------------------------------------------------------------------
# trace propagation: mechanics + off-path identity
# ---------------------------------------------------------------------------


class TestTracePropagation:
    def test_off_by_default_and_stamp_is_identity(self):
        assert obs_trace.propagation_enabled() is False
        meta = {"seq": 3}
        # the wire-identity contract: the off path returns the SAME
        # object, so no transport encodes anything extra
        assert obs_trace.stamp(meta) is meta
        assert obs_trace.stamp(None) is None
        assert obs_trace.extract({"trace_id": "t"}) is None

    def test_stamp_mints_and_does_not_mutate(self):
        meta = {"seq": 1}
        with obs_trace.use_propagation(True):
            out = obs_trace.stamp(meta)
        assert meta == {"seq": 1}  # input untouched
        assert out["seq"] == 1
        assert len(out["trace_id"]) == 32
        assert len(out["span_id"]) == 16

    def test_scope_continues_trace_across_stamp(self):
        with obs_trace.use_propagation(True):
            with obs_trace.trace_scope("feedcafe" * 4):
                a = obs_trace.stamp({})
                b = obs_trace.stamp({})
            assert a["trace_id"] == b["trace_id"] == "feedcafe" * 4
            assert a["span_id"] != b["span_id"]

    def test_extracted_binds_consume_side_context(self):
        with obs_trace.use_propagation(True):
            wire = obs_trace.stamp({"seq": 9})
            assert obs_trace.current_trace() is None
            with obs_trace.extracted(wire) as ctx:
                assert ctx[0] == wire["trace_id"]
                assert obs_trace.current_trace() == ctx
            assert obs_trace.current_trace() is None
            # foreign/malformed metas never raise, never bind
            with obs_trace.extracted({"trace_id": 7}) as ctx:
                assert ctx is None

    def test_spans_carry_bound_trace_id(self):
        tracer = Tracer()
        with obs_trace.use_propagation(True):
            with obs_trace.trace_scope("ab" * 16):
                with tracer.span("work", "test"):
                    pass
                tracer.instant("mark", "test")
        events = tracer.events()
        assert all(e["args"]["trace_id"] == "ab" * 16 for e in events)

    def test_spans_unstamped_when_off(self):
        tracer = Tracer()
        with tracer.span("work", "test"):
            pass
        assert "trace_id" not in tracer.events()[0].get("args", {})

    def test_scope_follows_created_tasks(self):
        async def main():
            with obs_trace.use_propagation(True):
                with obs_trace.trace_scope("cd" * 16):
                    task = asyncio.create_task(_child())
                return await task

        async def _child():
            return obs_trace.current_trace()

        ctx = _run(main())
        assert ctx[0] == "cd" * 16


class TestHLOIdentityWithPropagation:
    @pytest.mark.parametrize("impl", ["scan", "scan2"])
    def test_block_jit_identical_on_vs_off(self, impl):
        """Propagation is host-side only: the reduce block jit must
        lower to byte-identical HLO whether or not stamping is enabled
        and a trace context is bound while building/lowering."""
        from tmhpvsim_tpu.engine import Simulation

        def lowered() -> str:
            sim = Simulation(scfg(block_impl=impl))
            state = sim.init_state()
            acc = sim.init_reduce_acc()
            inputs, _ = sim.host_inputs(0)
            jit = (sim._scan_acc_jit if impl == "scan"
                   else sim._scan2_acc_jit)
            return jit.lower(state, inputs, acc).as_text()

        off = lowered()
        with obs_trace.use_propagation(True), \
                obs_trace.trace_scope(obs_trace.new_trace_id()):
            on = lowered()
        assert on == off


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------


@pytest.mark.netport
class TestObsServerEndpoints:
    def test_metrics_healthz_readyz_flight(self):
        async def main():
            reg = MetricsRegistry()
            reg.counter("engine.blocks").inc(2)
            tracer = Tracer()
            tracer.instant("block", "engine", block=0)
            state = {"ok": False}
            obs = ObsServer(0, registry=reg, tracer=tracer,
                            ready=lambda: (state["ok"],
                                           {"detail": "warming"}))
            await obs.start()
            assert obs.port != 0  # resolved from the ephemeral bind
            try:
                st, hd, body = await _http_get(obs.port, "/healthz")
                assert st == 200 and body == b"ok\n"

                st, hd, body = await _http_get(obs.port, "/metrics")
                assert st == 200
                assert hd["content-type"] == OPENMETRICS_CONTENT_TYPE
                text = body.decode()
                assert "tmhpvsim_engine_blocks_total 2" in text
                assert text.endswith("# EOF\n")
                # the scrape itself is counted — visible next scrape
                st, _, body = await _http_get(obs.port, "/metrics")
                assert b"tmhpvsim_obs_live_requests_total" in body

                st, _, body = await _http_get(obs.port, "/readyz")
                assert st == 503
                assert json.loads(body) == {"detail": "warming",
                                            "ready": False}
                state["ok"] = True
                st, _, body = await _http_get(obs.port, "/readyz")
                assert st == 200 and json.loads(body)["ready"] is True

                st, hd, body = await _http_get(obs.port, "/flight")
                assert st == 200
                doc = json.loads(body)
                names = [e.get("name") for e in doc["traceEvents"]]
                assert "block" in names

                assert (await _http_get(obs.port, "/nope"))[0] == 404
            finally:
                await obs.stop()
        _run(main())

    def test_flight_404_when_tracing_off(self):
        async def main():
            obs = await ObsServer(0, registry=MetricsRegistry()).start()
            try:
                st, _, body = await _http_get(obs.port, "/flight")
                assert st == 404 and b"tracing off" in body
            finally:
                await obs.stop()
        _run(main())

    def test_non_get_is_405_and_broken_probe_is_503(self):
        async def main():
            def broken():
                raise RuntimeError("probe exploded")

            obs = ObsServer(0, registry=MetricsRegistry(), ready=broken)
            await obs.start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1",
                                                     obs.port)
                w.write(b"POST /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
                await w.drain()
                raw = await r.read()
                w.close()
                assert b"405" in raw.split(b"\r\n", 1)[0]

                st, _, body = await _http_get(obs.port, "/readyz")
                assert st == 503
                assert "probe exploded" in json.loads(body)["error"]
            finally:
                await obs.stop()
        _run(main())

    def test_threaded_lifecycle_and_bind_error_in_caller(self):
        reg = MetricsRegistry()
        reg.gauge("device.cost.north_star_frac").set(0.18)
        obs = ObsServer(0, registry=reg).start_threaded()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{obs.port}/metrics",
                    timeout=5) as resp:
                text = resp.read().decode()
            assert "tmhpvsim_device_cost_north_star_frac 0.18" in text
            # a second server on the SAME port: the bind error must
            # surface in the caller, synchronously, not on the thread
            clash = ObsServer(obs.port, registry=reg)
            with pytest.raises(OSError):
                clash.start_threaded()
        finally:
            obs.close_threaded()
        # idempotent close
        obs.close_threaded()

    def test_maybe_obs_server_none_is_inert(self):
        async def main():
            async with maybe_obs_server(None) as obs:
                assert obs is None
        _run(main())


# ---------------------------------------------------------------------------
# /readyz semantics: warm-up, breaker chaos, drain — no sleeps
# ---------------------------------------------------------------------------


@pytest.mark.netport
@pytest.mark.chaos
class TestReadyzServeSemantics:
    def test_readyz_tracks_warmup_breaker_and_drain(self):
        url = "local://readyz-chaos"
        cfg = ServeConfig(sim=scfg(), url=url, window_s=0.05,
                          batch_sizes=(1,), timeout_s=300.0,
                          breaker_threshold=2, breaker_reset_s=60.0)
        scen = {"demand_scale": 1.1, "horizon_s": 120}

        async def ask(client, timeout=60.0):
            return await client.request(scen, timeout=timeout)

        async def readyz(port):
            st, _, body = await _http_get(port, "/readyz")
            return st, json.loads(body)

        async def main():
            reg = MetricsRegistry()
            with use_registry(reg):
                server = ScenarioServer(cfg, registry=reg)
                obs = ObsServer(0, registry=reg,
                                ready=server.readiness)
                await obs.start()
                try:
                    # before warm-up: 503, and the detail says why
                    st, body = await readyz(obs.port)
                    assert st == 503 and body["warm"] is False

                    await server.start()
                    st, body = await readyz(obs.port)
                    assert st == 200 and body == {
                        "breaker": "closed", "draining": False,
                        "ready": True, "warm": True}

                    # drive the breaker with injected dispatch faults
                    # and an injected clock — deterministic, no sleeps
                    clock = [1000.0]
                    breaker = server.batcher.breaker
                    breaker._now = lambda: clock[0]
                    async with ScenarioClient(url) as c:
                        with faults.active(FaultPlan.parse(
                                "serve.dispatch=raise@n1x2")):
                            for _ in range(2):
                                r = await ask(c)
                                assert not r["ok"]
                                assert r["error"]["code"] == "internal"
                        st, body = await readyz(obs.port)
                        assert st == 503 and body["breaker"] == "open"

                        # past reset_s: half-open is still NOT ready
                        # (the probe hasn't proven anything yet)
                        clock[0] += cfg.breaker_reset_s + 1
                        st, body = await readyz(obs.port)
                        assert st == 503
                        assert body["breaker"] == "half_open"

                        # a successful probe closes it: ready again
                        r = await ask(c)
                        assert r["ok"]
                        st, body = await readyz(obs.port)
                        assert st == 200 and body["breaker"] == "closed"

                    # draining: immediately not ready
                    server.begin_drain()
                    st, body = await readyz(obs.port)
                    assert st == 503 and body["draining"] is True
                finally:
                    await obs.stop()
                    await server.stop()
        _run(main())


# ---------------------------------------------------------------------------
# the serve soak: 8 clients, one trace id across the whole path,
# stitched per-process timelines — on all three transports
# ---------------------------------------------------------------------------


N_SOAK_CLIENTS = 8


async def _soak(url, tmp_path, tag):
    """8 concurrent clients against a warm server with propagation on;
    returns after asserting the stitched client/server timelines
    correlate every request end to end."""
    cfg = ServeConfig(sim=scfg(), url=url, window_s=0.1,
                      batch_sizes=(1, 4, 8), timeout_s=300.0)
    reg = MetricsRegistry()
    tracer = Tracer()
    with use_registry(reg), obs_trace.use_tracer(tracer), \
            obs_trace.use_propagation(True):
        server = ScenarioServer(cfg, registry=reg)
        await server.start()
        clients = [ScenarioClient(url) for _ in range(N_SOAK_CLIENTS)]
        try:
            for c in clients:
                await c.__aenter__()
            replies = await asyncio.gather(*[
                clients[i].request(
                    {"demand_scale": 1.0 + 0.05 * i, "horizon_s": 120},
                    rid=f"{tag}-{i}", timeout=300)
                for i in range(N_SOAK_CLIENTS)])
            assert all(r["ok"] for r in replies), replies
        finally:
            for c in clients:
                await c.__aexit__(None, None, None)
            await server.stop()
    _assert_stitched_correlation(tracer, tmp_path, tag)


def _assert_stitched_correlation(tracer, tmp_path, tag):
    """Split the in-process soak's ring into the client-side and
    server-side timelines (stand-ins for the two processes' trace
    files), stitch them with tools/trace_stats.py, and prove one id
    correlates client → batcher → fused dispatch → reply."""
    events = tracer.events()
    client_evs = [e for e in events
                  if str(e.get("name", "")).startswith("client.")]
    server_evs = [e for e in events
                  if not str(e.get("name", "")).startswith("client.")]
    cpath = tmp_path / f"{tag}-client.json"
    spath = tmp_path / f"{tag}-server.json"
    merged_path = tmp_path / f"{tag}-all.json"
    tracer.export(str(cpath), "client", events=client_evs)
    tracer.export(str(spath), "server", events=server_evs)

    out = subprocess.run(
        [sys.executable, str(TRACE_STATS), str(cpath), str(spath),
         "--stitch", str(merged_path)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "stitched 2 file(s)" in out.stdout
    assert "trace_id" in out.stdout  # the correlation table printed

    merged = json.loads(merged_path.read_text())
    errors, evs = trace_stats.validate(merged)
    assert not errors, errors
    groups = trace_stats.trace_groups(evs)

    # one trace id per logical request, learned from the client side
    rid_tid = {e["args"]["id"]: e["args"]["trace_id"]
               for e in client_evs if e["name"] == "client.publish"}
    assert len(rid_tid) == N_SOAK_CLIENTS
    assert len(set(rid_tid.values())) == N_SOAK_CLIENTS
    for rid, tid in rid_tid.items():
        group = groups[tid]
        names = {e["name"] for e in group}
        # the whole path under ONE id: client publish → batcher
        # admission → the fused dispatch (claimed via its trace_ids
        # list) → the client-side reply
        assert {"client.publish", "batcher.admit",
                "batcher.dispatch", "client.reply"} <= names, (rid, names)
        # and it spans both stitched "processes"
        assert len({e["pid"] for e in group}) >= 2, (rid, group)


class TestSoakTraceCorrelation:
    def test_local_transport(self, tmp_path):
        _run(_soak("local://soak-trace", tmp_path, "local"))

    @pytest.mark.netport
    def test_tcp_transport(self, tmp_path):
        async def main():
            async with TcpFanoutBroker(port=0) as broker:
                await _soak(f"tcp://127.0.0.1:{broker.port}",
                            tmp_path, "tcp")
        _run(main())

    def test_amqp_transport(self, tmp_path, fake_aio_pika):  # noqa: F811
        _run(_soak("amqp://fake-host:5672/", tmp_path, "amqp"))


# ---------------------------------------------------------------------------
# pvsim --backend=jax end to end: live readiness + cost gauges mid-run
# ---------------------------------------------------------------------------


@pytest.mark.netport
def test_pvsim_jax_obs_endpoint_live(tmp_path, monkeypatch):
    """`pvsim --backend=jax --obs-port 0`: /readyz flips to 200 once the
    first block lands, /metrics serves the device.cost.* gauges
    mid-run, and the socket is gone after the run.  The probe runs from
    inside the per-block gauge publish (the obs endpoint answers on its
    own thread), so the scrape is deterministically mid-run."""
    from tmhpvsim_tpu.apps import pvsim as app
    from tmhpvsim_tpu.obs import live as live_mod

    captured = {}
    orig_cls = live_mod.ObsServer

    class Capturing(orig_cls):
        def start_threaded(self):
            super().start_threaded()
            captured["srv"] = self
            return self

    monkeypatch.setattr(live_mod, "ObsServer", Capturing)

    results = {}
    real_publish = obs_cost.publish_gauges

    def probing_publish(registry, doc, prefix="device.cost."):
        real_publish(registry, doc, prefix)
        if "metrics" in results or "srv" not in captured:
            return
        port = captured["srv"].port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=10) as resp:
            results["ready"] = json.loads(resp.read())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            results["metrics"] = resp.read().decode()

    monkeypatch.setattr(obs_cost, "publish_gauges", probing_publish)
    try:
        app.pvsim_jax(str(tmp_path / "out.csv"), duration_s=300,
                      n_chains=4, seed=7, start="2019-09-05 10:00:00",
                      block_s=60, output="reduce", block_impl="scan",
                      obs_port=0)
    finally:
        obs_trace.enable_propagation(False)  # app enables; tests restore
    assert "srv" in captured, "obs server was never started"
    assert results.get("ready", {}).get("warm") is True, results
    assert results["ready"]["blocks"] >= 1
    assert "tmhpvsim_device_cost_north_star_frac" in results["metrics"]
    assert "tmhpvsim_device_cost_site_s_per_s" in results["metrics"]
    # after the run, the listener is down
    srv = captured["srv"]
    with pytest.raises(OSError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=2)


# ---------------------------------------------------------------------------
# tools: stitcher, cost_report, bench_trend cost column
# ---------------------------------------------------------------------------


class TestTraceStatsStitch:
    def _docs(self):
        client = {"traceEvents": [
            {"ph": "X", "name": "serve.request", "cat": "serve",
             "ts": 10, "dur": 500, "pid": 41, "tid": 1,
             "args": {"trace_id": "t-aaa"}},
            {"ph": "X", "name": "serve.request", "cat": "serve",
             "ts": 20, "dur": 400, "pid": 41, "tid": 1,
             "args": {"trace_id": "t-bbb"}},
        ]}
        server = {"traceEvents": [
            {"ph": "i", "name": "batcher.admit", "cat": "serve",
             "ts": 60, "pid": 41, "tid": 2,
             "args": {"trace_id": "t-aaa"}},
            {"ph": "X", "name": "batcher.dispatch", "cat": "serve",
             "ts": 100, "dur": 300, "pid": 41, "tid": 2,
             "args": {"trace_ids": ["t-aaa", "t-bbb"]}},
        ]}
        return client, server

    def test_stitch_remaps_colliding_pids(self):
        client, server = self._docs()
        merged = trace_stats.stitch([
            ("client.json", client["traceEvents"]),
            ("server.json", server["traceEvents"])])
        errors, evs = trace_stats.validate(merged)
        assert not errors, errors
        # same os pid 41 in both files -> two distinct tracks, labelled
        pids = {e["pid"] for e in evs if e.get("ph") != "M"}
        assert len(pids) == 2
        labels = {e["args"]["name"] for e in evs
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name"}
        assert labels == {"client.json:41", "server.json:41"}

    def test_trace_groups_expand_dispatch_trace_ids(self):
        client, server = self._docs()
        merged = trace_stats.stitch([
            ("c", client["traceEvents"]), ("s", server["traceEvents"])])
        groups = trace_stats.trace_groups(merged)
        assert set(groups) == {"t-aaa", "t-bbb"}
        # the one fused dispatch span is claimed by BOTH traces
        assert len(groups["t-aaa"]) == 3
        assert len(groups["t-bbb"]) == 2
        for tid in groups:
            assert any(e["name"] == "batcher.dispatch"
                       for e in groups[tid])

    def test_cli_stitch_round_trip(self, tmp_path):
        client, server = self._docs()
        c, s = tmp_path / "c.json", tmp_path / "s.json"
        c.write_text(json.dumps(client))
        s.write_text(json.dumps(server))
        out_path = tmp_path / "all.json"
        out = subprocess.run(
            [sys.executable, str(TRACE_STATS), str(c), str(s),
             "--stitch", str(out_path)],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        assert "2 trace id(s)" in out.stdout
        assert "t-aaa" in out.stdout
        # the stitched file itself revalidates through the same tool
        again = subprocess.run(
            [sys.executable, str(TRACE_STATS), "-q", str(out_path)],
            capture_output=True, text=True)
        assert again.returncode == 0, again.stderr

    def test_stitch_refused_on_invalid_input(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [{"ph": "X"}]}))
        out = subprocess.run(
            [sys.executable, str(TRACE_STATS), str(bad),
             "--stitch", str(tmp_path / "all.json")],
            capture_output=True, text=True)
        assert out.returncode == 1
        assert not (tmp_path / "all.json").exists()


class TestCostReportTool:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_valid_docs_print_and_pass(self, tmp_path):
        cost = obs_cost.cost_doc(site_s_per_s=1.2e9,
                                 block_impl="scan2",
                                 compute_dtype="bf16",
                                 kernel_impl="table",
                                 device_kind="TPU v5 lite")
        rep = self._write(tmp_path, "rep.json",
                          {"schema_version": 10, "cost": cost})
        head = self._write(tmp_path, "head.json", {
            "variants": {"scan2": {"rate": 1.2e9, "cost": cost}},
            "run_report": {"cost": cost}})
        out = subprocess.run(
            [sys.executable, str(COST_REPORT), rep, head],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        assert "scan2/bf16/table" in out.stdout
        assert "north-star" in out.stdout
        assert "variants.scan2.cost" in out.stdout

    def test_pre_v10_doc_passes_unless_required(self, tmp_path):
        old = self._write(tmp_path, "old.json", {"schema_version": 7})
        ok = subprocess.run([sys.executable, str(COST_REPORT), old],
                            capture_output=True, text=True)
        assert ok.returncode == 0
        req = subprocess.run(
            [sys.executable, str(COST_REPORT), old, "--require"],
            capture_output=True, text=True)
        assert req.returncode == 1

    def test_malformed_cost_fails(self, tmp_path):
        bad_cost = obs_cost.cost_doc(site_s_per_s=1e6)
        del bad_cost["model"]
        bad = self._write(tmp_path, "bad.json", {"cost": bad_cost})
        out = subprocess.run([sys.executable, str(COST_REPORT), bad],
                             capture_output=True, text=True)
        assert out.returncode == 1
        assert "INVALID" in out.stdout


class TestBenchTrendCostColumn:
    def _artifact(self, tmp_path, name, rate, steady):
        cost = obs_cost.cost_doc(site_s_per_s=rate,
                                 device_kind="TPU v5 lite")
        doc = {"best": "scan", "rate": rate,
               "variants": {"scan": {"rate": rate, "cost": cost}},
               "run_report": {"schema_version": 10, "cost": cost,
                              "timing": {"steady_block_s": steady}}}
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_cost_column_and_gate_suffix(self, tmp_path):
        a = self._artifact(tmp_path, "BENCH_r01.json", 1.2e9, 0.5)
        b = self._artifact(tmp_path, "BENCH_r02.json", 1.25e9, 0.49)
        out = subprocess.run([sys.executable, str(BENCH_TREND), a, b],
                             capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        header = out.stdout.splitlines()[0]
        assert "cost" in header.split()
        assert "0.183" in out.stdout or "0.182" in out.stdout
        assert "north_star_frac=" in out.stdout
        assert "%" in out.stdout  # the vpu roofline rides along

    def test_pre_v10_rows_show_dash(self, tmp_path):
        doc = {"best": "scan", "rate": 1e9,
               "run_report": {"schema_version": 9,
                              "timing": {"steady_block_s": 0.5}}}
        p = tmp_path / "BENCH_r01.json"
        p.write_text(json.dumps(doc))
        out = subprocess.run([sys.executable, str(BENCH_TREND), str(p)],
                             capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        row = [ln for ln in out.stdout.splitlines()
               if "BENCH_r01" in ln][0]
        assert " - " in row  # no cost section -> dash, not a crash


# ---------------------------------------------------------------------------
# acceptance: stamped-path steady-block overhead at 65536 chains (slow
# lane via conftest._SLOW_LANE)
# ---------------------------------------------------------------------------


def test_trace_stamp_overhead_65536_chains():
    """With the ops plane ON (propagation enabled, a trace context
    bound, per-block cost gauges published) the 65536-chain CPU
    engine's steady block walls must stay within 1% of the all-off
    path.  min-of-blocks on both arms filters scheduler noise."""
    from tmhpvsim_tpu.engine import Simulation

    def steady_min(stamped: bool) -> float:
        reg = MetricsRegistry(enabled=stamped)
        tracer = Tracer() if stamped else None
        cfg = SimConfig(
            start="2019-09-05 10:00:00", duration_s=4 * 60,
            n_chains=65536, seed=7, block_s=60, dtype="float32",
            block_impl="wide", output="reduce")
        ctx = (obs_trace.use_propagation(True) if stamped
               else contextlib.nullcontext())
        with use_registry(reg), ctx, obs_trace.trace_scope(
                obs_trace.new_trace_id() if stamped else None):
            sim = Simulation(cfg)

            def on_block(bi, state, acc):
                if not stamped:
                    return
                tracer.instant("block", "engine", block=bi)
                rate = sim.timer.rate()
                if rate:
                    obs_cost.publish_gauges(reg, obs_cost.cost_doc(
                        site_s_per_s=rate, block_impl="wide",
                        device_kind="cpu"))

            sim.run_reduced(on_block=on_block)
        return min(sim.timer.block_times)

    steady_min(True)  # warm the jit + persistent cache
    plain = steady_min(False)
    stamped = steady_min(True)
    assert stamped <= plain * 1.01, (
        f"stamped-path block overhead {stamped / plain - 1:.2%} exceeds "
        f"1% (stamped {stamped:.4f} s vs plain {plain:.4f} s)")
