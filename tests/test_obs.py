"""Observability subsystem tests: metrics registry semantics, sinks,
BlockTimer compile/steady split, the device-trace platform guard,
RunReport schema validation, pacing monitor, and the end-to-end
``pvsim --metrics/--run-report`` smoke (tests/test_obs.py is named by
obs/metrics.py as the home of the 65536-chain overhead assertion)."""

import json
import logging
import os

import pytest

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs.metrics import (
    JsonlSink,
    MetricsRegistry,
    PrometheusSink,
    make_sink,
    use_registry,
)
from tmhpvsim_tpu.obs.profiler import (
    MANIFEST_NAME,
    BlockTimer,
    PlatformMismatchError,
    device_trace,
    read_manifest,
)
from tmhpvsim_tpu.obs.report import (
    REPORT_KIND,
    REPORT_SCHEMA_VERSION,
    RunReport,
    validate_report,
)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_accumulates(self):
        reg = MetricsRegistry()
        c = reg.counter("x")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5

    def test_counter_rejects_decrease(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("x").inc(-1)

    def test_gauge_set_and_add(self):
        g = MetricsRegistry().gauge("g")
        g.set(4.0)
        g.add(-1.5)
        assert g.value == 2.5

    def test_histogram_stats_and_buckets(self):
        h = MetricsRegistry().histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(55.55)
        assert snap["min"] == 0.05
        assert snap["max"] == 50.0
        assert snap["mean"] == pytest.approx(55.55 / 4)
        # cumulative per Prometheus semantics; the 50.0 obs only lands
        # in the implicit +Inf bucket (count)
        assert snap["buckets"] == [[0.1, 1], [1.0, 2], [10.0, 3]]

    def test_same_name_same_object(self):
        reg = MetricsRegistry()
        assert reg.counter("n") is reg.counter("n")

    def test_type_collision_raises(self):
        reg = MetricsRegistry()
        reg.counter("n")
        with pytest.raises(TypeError):
            reg.gauge("n")

    def test_disabled_registry_is_noop(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("x")
        c.inc(5)
        reg.gauge("g").set(1.0)
        reg.histogram("h").observe(2.0)
        assert c.value == 0.0
        assert reg.snapshot() == {"counters": {}, "gauges": {},
                                  "histograms": {}}

    def test_timed_nests(self):
        reg = MetricsRegistry()
        with reg.timed("outer"):
            with reg.timed("inner"):
                pass
        snap = reg.snapshot()["histograms"]
        assert snap["outer"]["count"] == 1
        assert snap["inner"]["count"] == 1
        assert snap["outer"]["sum"] >= snap["inner"]["sum"]

    def test_use_registry_swaps_default(self):
        fresh = MetricsRegistry()
        prev = obs_metrics.get_registry()
        with use_registry(fresh):
            assert obs_metrics.get_registry() is fresh
        assert obs_metrics.get_registry() is prev


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

class TestSinks:
    def test_jsonl_round_trip(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg = MetricsRegistry()
        reg.add_sink(JsonlSink(path))
        reg.counter("blocks").inc()
        reg.flush(event="block")
        reg.counter("blocks").inc()
        reg.flush(event="end")
        reg.close()
        lines = [json.loads(ln) for ln in open(path)]
        assert [ln["event"] for ln in lines] == ["block", "end"]
        assert lines[0]["metrics"]["counters"]["blocks"] == 1
        assert lines[1]["metrics"]["counters"]["blocks"] == 2

    def test_prometheus_round_trip(self, tmp_path):
        path = str(tmp_path / "m.prom")
        reg = MetricsRegistry()
        reg.counter("engine.blocks_total").inc(3)
        reg.gauge("engine.compile_s").set(1.5)
        reg.histogram("engine.block_wall_s").observe(0.7)
        reg.add_sink(PrometheusSink(path))
        reg.flush()
        reg.close()
        text = open(path).read()
        assert "# TYPE tmhpvsim_engine_blocks_total counter" in text
        assert "tmhpvsim_engine_blocks_total 3" in text
        assert "tmhpvsim_engine_compile_s 1.5" in text
        assert 'tmhpvsim_engine_block_wall_s_bucket{le="+Inf"} 1' in text
        assert "tmhpvsim_engine_block_wall_s_count 1" in text

    def test_make_sink_dispatch(self, tmp_path):
        assert isinstance(make_sink(str(tmp_path / "a.prom")),
                          PrometheusSink)
        assert isinstance(make_sink(str(tmp_path / "a.jsonl")), JsonlSink)

    def test_flush_survives_sink_oserror(self, tmp_path):
        reg = MetricsRegistry()
        sink = JsonlSink(str(tmp_path / "m.jsonl"))
        sink._f.close()  # provoke "write to closed file"
        reg.add_sink(sink)
        reg.counter("x").inc()
        reg.flush()  # must not raise


# ---------------------------------------------------------------------------
# BlockTimer: compile vs steady split (satellite 1 regression)
# ---------------------------------------------------------------------------

class TestBlockTimer:
    def test_single_block_has_no_steady(self):
        t = BlockTimer(n_chains=4, block_s=60, log=False)
        t.tick()
        s = t.summary()
        assert s["n_blocks_timed"] == 1
        assert s["compile_s"] is not None
        assert s["first_block_s"] == s["compile_s"]
        # the old summary() passed the compile-inclusive block off as
        # steady_block_s; it must be None when no steady block exists
        assert s["steady_block_s"] is None
        assert s["rate_includes_compile"] is True
        assert s["site_seconds_per_s"] > 0

    def test_zero_blocks(self):
        s = BlockTimer(4, 60, log=False).summary()
        assert s["n_blocks_timed"] == 0
        assert s["compile_s"] is None
        assert s["steady_block_s"] is None
        assert s["site_seconds_per_s"] == 0.0

    def test_multi_block_splits_and_feeds_registry(self):
        reg = MetricsRegistry()
        t = BlockTimer(4, 60, log=False, registry=reg, prefix="engine")
        for _ in range(3):
            t.tick()
        s = t.summary()
        assert s["n_blocks_timed"] == 3
        assert s["steady_block_s"] is not None
        assert s["rate_includes_compile"] is False
        snap = reg.snapshot()
        assert "engine.compile_s" in snap["gauges"]
        assert snap["histograms"]["engine.block_wall_s"]["count"] == 2


# ---------------------------------------------------------------------------
# device trace platform guard (satellite 2 + acceptance regression)
# ---------------------------------------------------------------------------

class TestPlatformGuard:
    def test_manifest_records_traced_platform(self, tmp_path):
        import jax
        import jax.numpy as jnp

        d = str(tmp_path / "trace")
        with device_trace(d):
            jnp.zeros(8).block_until_ready()
        m = read_manifest(d)
        assert m is not None
        assert m["traced_platform"] == jax.default_backend() == "cpu"
        assert m["expected_platform"] is None
        assert m["platform_mismatch"] is False

    def test_mismatch_warns_and_tags(self, tmp_path, caplog):
        import jax.numpy as jnp

        d = str(tmp_path / "trace")
        with caplog.at_level(logging.WARNING,
                             logger="tmhpvsim_tpu.obs.profiler"):
            with device_trace(d, expect_platform="tpu"):
                jnp.zeros(8).block_until_ready()
        m = read_manifest(d)
        assert m["platform_mismatch"] is True
        assert m["expected_platform"] == "tpu"
        assert any("platform_mismatch" in r.message for r in caplog.records)

    def test_strict_raises_but_still_writes_manifest(self, tmp_path):
        import jax.numpy as jnp

        d = str(tmp_path / "trace")
        with pytest.raises(PlatformMismatchError):
            with device_trace(d, expect_platform="tpu", strict=True):
                jnp.zeros(8).block_until_ready()
        assert read_manifest(d)["platform_mismatch"] is True

    def test_expect_env_default(self, tmp_path, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("TMHPVSIM_EXPECT_PLATFORM", "tpu")
        d = str(tmp_path / "trace")
        with device_trace(d):
            jnp.zeros(8).block_until_ready()
        assert read_manifest(d)["platform_mismatch"] is True

    def test_missing_manifest_reads_none(self, tmp_path):
        assert read_manifest(str(tmp_path)) is None

    def test_engine_profiling_shim_removed(self):
        # the deprecation shim had one full release of warning (PR 3)
        # and was removed; a resurrected engine.profiling would silently
        # re-bless the old import path, so its absence is asserted
        # (migration note in MIGRATION.md points to obs.profiler)
        import importlib.util

        assert importlib.util.find_spec(
            "tmhpvsim_tpu.engine.profiling") is None


# ---------------------------------------------------------------------------
# RunReport schema
# ---------------------------------------------------------------------------

class TestRunReport:
    def test_minimal_report_validates(self):
        doc = RunReport("test").doc()
        assert doc["schema_version"] == REPORT_SCHEMA_VERSION
        assert doc["kind"] == REPORT_KIND
        assert doc["device"]["platform"] == "cpu"
        validate_report(doc)

    def test_write_is_valid_json(self, tmp_path):
        path = str(tmp_path / "sub" / "r.json")
        RunReport("test").write(path)
        validate_report(json.load(open(path)))

    def test_rejects_missing_required(self):
        doc = RunReport("test").doc()
        del doc["app"]
        with pytest.raises(ValueError, match="app"):
            validate_report(doc)

    def test_rejects_wrong_schema_version(self):
        doc = RunReport("test").doc()
        doc["schema_version"] = 999
        with pytest.raises(ValueError, match="schema_version"):
            validate_report(doc)

    def test_rejects_unknown_top_level_key(self):
        doc = RunReport("test").doc()
        doc["surprise"] = 1
        with pytest.raises(ValueError, match="unknown keys"):
            validate_report(doc)

    def test_rejects_mistyped_section(self):
        doc = RunReport("test").doc()
        doc["timing"] = "fast"
        with pytest.raises(ValueError, match="timing"):
            validate_report(doc)

    def test_rejects_unserialisable(self):
        doc = RunReport("test").doc()
        doc["headline"] = {"x": object()}
        with pytest.raises(ValueError, match="serialisable"):
            validate_report(doc)

    def test_attach_metrics_derives_sections(self):
        reg = MetricsRegistry()
        reg.histogram("checkpoint.save_s").observe(0.2)
        reg.gauge("slab.total").set(3)
        reg.gauge("slab.completed").set(2)
        reg.gauge("clock.pacing_lag_s").set(1.0)
        reg.gauge("clock.pacing_slip_total_s").set(4.5)
        rep = RunReport("test")
        rep.attach_metrics(reg)
        doc = rep.doc()
        assert doc["checkpoint"]["saves"] == 1
        assert doc["checkpoint"]["save_total_s"] == pytest.approx(0.2)
        assert doc["slabs"] == {"completed": 2, "total": 3}
        assert doc["realtime"]["pacing_slip_total_s"] == 4.5

    def test_config_echo_compacts_site_grid(self):
        from tmhpvsim_tpu.config import SiteGrid, SimConfig

        grid = SiteGrid.regular((45.0, 46.0), (5.0, 6.0), 3, 4)
        cfg = SimConfig(start="2019-09-05 10:00:00", duration_s=120,
                        n_chains=12, seed=1, block_s=60, site_grid=grid)
        doc = RunReport("test", config=cfg).doc()
        assert doc["config"]["site_grid"] == {"n_sites": 12}


# ---------------------------------------------------------------------------
# pacing monitor (satellite 3)
# ---------------------------------------------------------------------------

class TestPacingMonitor:
    def test_slip_accumulates_only_new_lag(self):
        from tmhpvsim_tpu.runtime.clock import PacingMonitor

        reg = MetricsRegistry()
        with use_registry(reg):
            mon = PacingMonitor(period=1.0, warn_every_s=10.0)
            mon.observe(0.5, now=0.0)   # under 2 periods: no warn
            mon.observe(3.0, now=1.0)
            mon.observe(2.0, now=2.0)   # recovering: no new slip
            mon.observe(4.0, now=3.0)
        g = reg.snapshot()["gauges"]
        assert g["clock.pacing_lag_s"] == 4.0
        # 0 -> 0.5 -> 3.0 -> (recover) -> 2.0 -> 4.0: new slip only
        assert g["clock.pacing_slip_total_s"] == pytest.approx(5.0)

    def test_warn_rate_limited(self, caplog):
        from tmhpvsim_tpu.runtime.clock import PacingMonitor

        with use_registry(MetricsRegistry()):
            mon = PacingMonitor(period=1.0, warn_every_s=10.0)
            with caplog.at_level(logging.WARNING,
                                 logger="tmhpvsim_tpu.runtime.clock"):
                assert mon.observe(3.0, now=0.0) is True
                assert mon.observe(4.0, now=5.0) is False   # rate-limited
                assert mon.observe(5.0, now=11.0) is True   # window over
                assert mon.observe(0.1, now=22.0) is False  # caught up
        warns = [r for r in caplog.records if "behind realtime" in r.message]
        assert len(warns) == 2
        assert "cumulative slip" in warns[0].message


# ---------------------------------------------------------------------------
# engine + app integration
# ---------------------------------------------------------------------------

def _small_cfg(**kw):
    from tmhpvsim_tpu.config import SimConfig

    base = dict(start="2019-09-05 10:00:00", duration_s=7200, n_chains=3,
                seed=7, block_s=3600, dtype="float32")
    base.update(kw)
    return SimConfig(**base)


class TestEngineIntegration:
    def test_run_reduced_report(self, tmp_path):
        from tmhpvsim_tpu.engine import Simulation

        reg = MetricsRegistry()
        with use_registry(reg):
            sim = Simulation(_small_cfg(output="reduce"))
            sim.run_reduced()
            path = str(tmp_path / "r.json")
            doc = sim.run_report(path=path)
        validate_report(doc)
        assert doc["app"] == "engine"
        assert doc["timing"]["n_blocks_timed"] == 2
        assert doc["timing"]["compile_s"] is not None
        assert doc["timing"]["steady_block_s"] is not None
        assert doc["plan"]["block_impl"] in ("wide", "scan", "scan2")
        assert doc["headline"]["site_seconds_per_s"] > 0
        assert doc["metrics"]["counters"]["engine.blocks_total"] == 2
        validate_report(json.load(open(path)))

    def test_run_ensemble_report(self):
        from tmhpvsim_tpu.engine import Simulation

        with use_registry(MetricsRegistry()):
            sim = Simulation(_small_cfg(output="ensemble"))
            for _ in sim.run_ensemble():
                pass
            doc = sim.run_report(app="engine.ensemble")
        validate_report(doc)
        assert doc["timing"]["n_blocks_timed"] == 2

    def test_gather_metrics_single_process(self):
        from tmhpvsim_tpu.parallel.distributed import gather_metrics

        snap = MetricsRegistry().snapshot()
        assert gather_metrics(snap) == [snap]


class TestCliSmoke:
    def test_cli_pvsim_metrics_run_report(self, tmp_path):
        """Acceptance smoke: pvsim --backend=jax emits both artifacts
        with valid schema."""
        from click.testing import CliRunner

        from tmhpvsim_tpu.cli import pvsim

        out = str(tmp_path / "out.csv")
        m_path = str(tmp_path / "m.jsonl")
        r_path = str(tmp_path / "r.json")
        r = CliRunner().invoke(pvsim, [
            out, "--backend", "jax", "--no-realtime",
            "--duration", "180", "--chains", "2", "--seed", "1",
            "--start", "2019-09-05 10:00:00",
            "--metrics", m_path, "--run-report", r_path,
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        doc = validate_report(json.load(open(r_path)))
        assert doc["app"] == "pvsim"
        assert doc["config"]["n_chains"] == 2
        assert doc["device"]["platform"] == "cpu"
        lines = [json.loads(ln) for ln in open(m_path)]
        assert lines, "no metric snapshots flushed"
        assert lines[-1]["event"] == "end"
        assert lines[-1]["metrics"]["counters"]["engine.blocks_total"] >= 1
        assert sum(1 for _ in open(out)) == 181  # header + 180 rows

    def test_cli_asyncio_backend_emits_observability(self, tmp_path):
        """The streaming (asyncio) backend accepts --metrics/--run-report
        too (it used to reject them): a bounded run with no producer
        still flushes metric snapshots and a schema-valid report whose
        app is the streaming consumer."""
        from click.testing import CliRunner

        from tmhpvsim_tpu.cli import pvsim

        m_path = str(tmp_path / "m.jsonl")
        r_path = str(tmp_path / "r.json")
        with use_registry(MetricsRegistry()):  # isolate rows_written == 0
            r = CliRunner().invoke(pvsim, [
                str(tmp_path / "o.csv"), "--no-realtime", "--seed", "1",
                "--duration", "2", "--amqp-url", "local://obs-cli",
                "--start", "2019-09-05 10:00:00",
                "--metrics", m_path, "--run-report", r_path,
            ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        doc = validate_report(json.load(open(r_path)))
        assert doc["app"] == "pvsim.stream"
        assert doc["streaming"] is not None
        assert doc["streaming"]["rows_written"] == 0  # no producer ran
        lines = [json.loads(ln) for ln in open(m_path)]
        assert lines and lines[-1]["event"] == "end"


# ---------------------------------------------------------------------------
# overhead acceptance: metrics enabled within 1% of disabled (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_metrics_overhead_65536_chains():
    """Steady-block wall with the metrics registry enabled (and a sink
    attached) must be within 1% of a disabled registry at the 65536-chain
    CPU config — the per-block hook cost is a handful of dict/float ops
    against an O(seconds) block wall.  min-of-steady-blocks on each arm
    filters scheduler noise on this 1-core host."""
    import tempfile

    from tmhpvsim_tpu.engine import Simulation

    def steady_min(enabled: bool) -> float:
        reg = MetricsRegistry(enabled=enabled)
        if enabled:
            with tempfile.TemporaryDirectory() as d:
                reg.add_sink(make_sink(os.path.join(d, "m.jsonl")))
                with use_registry(reg):
                    sim = Simulation(_small_cfg(
                        n_chains=65536, duration_s=4 * 60, block_s=60,
                        block_impl="wide", output="reduce"))
                    sim.run_reduced()
                    reg.flush(event="end")
                reg.close()
                return min(sim.timer.block_times)
        with use_registry(reg):
            sim = Simulation(_small_cfg(
                n_chains=65536, duration_s=4 * 60, block_s=60,
                block_impl="wide", output="reduce"))
            sim.run_reduced()
        return min(sim.timer.block_times)

    steady_min(True)  # warm the jit + persistent cache for both arms
    disabled = steady_min(False)
    enabled = steady_min(True)
    assert enabled <= disabled * 1.01, (
        f"metrics overhead {enabled / disabled - 1:.2%} exceeds 1% "
        f"(enabled {enabled:.4f} s vs disabled {disabled:.4f} s)"
    )
