"""Property-based tests (hypothesis): invariants under adversarial orders.

The example-based suite pins known scenarios; these push randomized
sequences through the pieces with subtle state — the funnel's eviction
heap, the renewal kernel, the time grid — asserting invariants that must
hold for EVERY input order.
"""

import asyncio
import datetime as dt
import math

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from tmhpvsim_tpu.runtime.funnel import SynchronizingFunnel
from tmhpvsim_tpu.models import renewal
from tmhpvsim_tpu.models.timegrid import TimeGridSpec

from collections import namedtuple

Rec = namedtuple("Rec", ["a", "b"])


def _drive_funnel(events, max_pending):
    """Apply (time, field) puts; return (emitted, funnel)."""

    async def run():
        q: asyncio.Queue = asyncio.Queue()
        f = SynchronizingFunnel(Rec, q, max_pending=max_pending)
        for time, field in events:
            await f.put(time, **{field: float(time)})
        out = []
        while not q.empty():
            out.append(q.get_nowait())
        return out, f

    return asyncio.run(run())


class TestFunnelProperties:
    @settings(max_examples=60, deadline=None)
    @given(st.lists(st.tuples(st.integers(0, 50),
                              st.sampled_from(["a", "b"])),
                    max_size=200),
           st.integers(2, 10))
    def test_heap_eviction_invariants(self, events, max_pending):
        """For ANY put order and cap: (1) the pending cache never exceeds
        the cap, (2) every emitted record is complete, (3) the age heap
        always covers the live cache (the lazy-deletion invariant that
        makes eviction pop-safe), (4) no timestamp is invented, and (5)
        heap bloat stays under the compaction bound."""
        emitted, f = _drive_funnel(events, max_pending)
        assert len(f._cache) <= max_pending
        assert set(f._cache) <= set(f._age_heap)
        for _, rec in emitted:
            assert not any(isinstance(v, float) and math.isnan(v)
                           for v in rec)
        # no timestamp is invented: everything emitted or pending came
        # from the input (a time CAN be both — a put after completion
        # legitimately starts a new partial record, reference semantics)
        times = {t for t, _ in events}
        emitted_t = {t for t, _ in emitted}
        assert emitted_t <= times and set(f._cache) <= times
        # heap bloat is bounded by the compaction backstop
        assert len(f._age_heap) <= 2 * max(len(f._cache), 1) + 64 + 1

    @settings(max_examples=30, deadline=None)
    @given(st.lists(st.integers(0, 30), min_size=1, max_size=120))
    def test_join_emits_exactly_matched_pairs(self, times_a):
        """Unbounded funnel: feeding stream a for times_a and stream b
        for every time must emit exactly the distinct times of times_a,
        each once, with both fields set."""

        async def run():
            q: asyncio.Queue = asyncio.Queue()
            f = SynchronizingFunnel(Rec, q, max_pending=None)
            for t in times_a:
                await f.put(t, a=float(t))
            for t in sorted(set(times_a)):
                await f.put(t, b=-float(t))
            out = []
            while not q.empty():
                out.append(q.get_nowait())
            return out, f

        out, f = asyncio.run(run())
        assert sorted(t for t, _ in out) == sorted(set(times_a))
        assert len(f._cache) == 0
        for t, rec in out:
            assert rec.a == float(t) and rec.b == -float(t)


class TestRenewalProperties:
    @settings(max_examples=40, deadline=None)
    @given(st.floats(0.01, 1.0), st.floats(0.5, 20.0),
           st.integers(0, 2**31 - 1))
    def test_cycle_respects_constraints(self, cc, ws, seed):
        """For any cloud cover, windspeed and draw: the sampled cycle
        keeps the exact cloud-fraction constraint and the 90-minute cap
        (models/renewal.py invariants (2)+(3)), cloud length positive."""
        rng = np.random.default_rng(seed)
        u = rng.random()
        cloud, total = renewal.cycle_from_u(
            np.float64(u), np.float64(cc), np.float64(ws)
        )
        cloud, total = float(cloud), float(total)
        cc_eff = min(max(cc, 1e-3), renewal.MAX_CLOUDCOVER)
        assert cloud > 0
        assert total * 0.999 <= cloud / cc_eff <= total * 1.001
        # the 90-min cap holds whenever it is REACHABLE: below
        # cap >= minimum transit length the constraint set is infeasible
        # (as in the reference's own algorithm for cc ~< 0.06) and the
        # kernel deliberately keeps only the cloud-fraction constraint
        from tmhpvsim_tpu.models import distributions as dist

        cap_m = renewal.MAX_CYCLE_S * cc_eff * ws
        if cap_m >= 2.0 * dist.CLOUD_LENGTH_XMIN_M:
            assert total <= renewal.MAX_CYCLE_S * 1.001
        else:
            # degenerate truncation: the kernel clamps the cap to twice
            # the minimum transit length (distributions.py) so the
            # truncated CDF stays well-defined — transit <= 2*xmin
            assert cloud <= 2.0 * dist.CLOUD_LENGTH_XMIN_M / ws * 1.001

    @settings(max_examples=20, deadline=None)
    @given(st.floats(0.05, 0.95), st.floats(1.0, 10.0),
           st.integers(0, 2**31 - 1), st.integers(100, 2000))
    def test_reference_renewal_emits_binary(self, cc, ws, seed, n):
        """The faithful reference algorithm emits only 0/1 and never gets
        stuck: any (cc, ws) produces n samples without error (run
        structure is covered distributionally by tests/test_renewal.py)."""
        r = renewal.ReferenceRenewal(cc, ws, np.random.default_rng(seed))
        vals = [next(r) for _ in range(n)]
        assert set(vals) <= {0, 1}


class TestTimeGridProperties:
    @settings(max_examples=25, deadline=None)
    @given(st.integers(0, 364), st.integers(0, 23), st.integers(0, 59),
           st.integers(61, 7200))
    def test_block_features_consistent(self, day, hour, minute, dur):
        """For arbitrary starts (including across DST transitions) and
        durations: fractions stay in [0,1), indices are nondecreasing,
        and minute indices advance exactly at 60-second boundaries of the
        local grid."""
        start = (dt.datetime(2019, 1, 1, hour, minute)
                 + dt.timedelta(days=day))
        spec = TimeGridSpec.from_local_start(
            start.isoformat(" "), dur, "Europe/Berlin"
        )
        blk = spec.block(0, dur)
        for frac in (blk.hour_fraction, blk.day_fraction, blk.min_fraction):
            assert (frac >= 0).all() and (frac < 1).all()
        for idx in (blk.hour_idx, blk.day_idx, blk.min_idx):
            assert (np.diff(idx) >= 0).all()
        assert blk.min_idx[0] == 0
        d = np.diff(blk.min_idx)
        assert set(np.unique(d)) <= {0, 1}
        # a minute interval on the local grid is 60 consecutive seconds
        changes = np.nonzero(d)[0]
        if len(changes) > 1:
            gaps = np.diff(changes)
            assert (gaps == 60).all()
